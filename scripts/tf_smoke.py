"""Term-frequency serving smoke (`make tf-smoke`): the ISSUE 14 fold
contracts end to end, across a REAL process boundary.

Process A trains a TF-flagged model, asserts the serve<->offline
TF-adjusted parity gate IN PROCESS (every served score bit-identical to
the offline frame's ``tf_match_probability`` for the same pair, fused and
unfused), exports the index + AOT sidecar and records its answers. It
also runs the legacy leg: a TF-LESS model's artifact round-trips and
serves bit-identically to its (unadjusted) offline scores with
``tf_active`` False — the fold never touches models that didn't opt in.

Process B — a fresh interpreter, no shared jit caches, no persistent
compile cache — restores the TF menu from the sidecar and asserts ZERO
backend compiles, zero cache reads, and first-query answers bit-identical
to process A's.

Exits nonzero on any violation. Runs on any backend (CPU tier included).
"""

import json
import os
import subprocess
import sys
import tempfile

os.environ.setdefault("JAX_PLATFORMS", "cpu")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

QUERY_HEAD = 80


def fixture_corpus(tf: bool = True):
    import numpy as np
    import pandas as pd

    rng = np.random.default_rng(7)
    firsts = ["amelia", "oliver", "isla", "george", "ava", "noah", "emily"]
    lasts = ["smith"] * 6 + ["jones", "taylor", "zorn"]
    n = 200
    df = pd.DataFrame(
        {
            "unique_id": range(n),
            "first_name": [str(rng.choice(firsts)) for _ in range(n)],
            "surname": [str(rng.choice(lasts)) for _ in range(n)],
            "dob": [f"19{rng.integers(40, 99)}" for _ in range(n)],
        }
    )
    settings = {
        "link_type": "dedupe_only",
        "comparison_columns": [
            {
                "col_name": "first_name",
                "num_levels": 3,
                "term_frequency_adjustments": tf,
            },
            {
                "col_name": "surname",
                "num_levels": 2,
                "comparison": {"kind": "exact"},
                "term_frequency_adjustments": tf,
            },
        ],
        "blocking_rules": ["l.dob = r.dob", "l.surname = r.surname"],
        "max_iterations": 5,
        # top_k must exceed the largest candidate block (the dominant
        # "smith" bucket) so the parity check covers EVERY offline pair
        "serve_top_k": 160,
        "serve_query_buckets": [16, 128],
        "serve_candidate_buckets": [64, 256],
    }
    return df, settings


def _assert_parity(df, df_e, index, engine, col):
    import numpy as np

    offline = {
        (r["unique_id_l"], r["unique_id_r"]): r[col]
        for _, r in df_e.iterrows()
    }
    top_p, top_rows, top_valid, _ = engine.query_arrays(df)
    served = set()
    checked = 0
    for q in range(len(df)):
        for r in range(top_p.shape[1]):
            if not top_valid[q, r]:
                continue
            m = int(index.unique_id[top_rows[q, r]])
            if m == q:
                continue
            key = (min(q, m), max(q, m))
            assert key in offline, f"served pair {key} missing offline"
            assert np.float32(offline[key]) == top_p[q, r], (
                f"serve<->offline {col} parity broke at {key}: "
                f"{offline[key]!r} != {top_p[q, r]!r}"
            )
            served.add(key)
            checked += 1
    assert served == set(offline), "serve must cover every offline pair"
    return checked


def phase_build(workdir: str) -> int:
    import numpy as np

    from splink_tpu import Splink
    from splink_tpu.serve import QueryEngine, load_index

    # ---- TF leg: fold parity, fused + unfused ----
    df, settings = fixture_corpus(tf=True)
    linker = Splink(settings, df=df)
    df_e = linker.get_scored_comparisons()
    assert "tf_match_probability" in df_e.columns
    index_dir = os.path.join(workdir, "index")
    linker.export_index(index_dir)
    index = load_index(index_dir)
    assert index.tf_fold_columns(), "fold data missing from the artifact"
    aot_dir = os.path.join(index_dir, "aot")
    engine = QueryEngine(index, aot_dir=aot_dir)
    assert engine.tf_active and engine._aot_binding()["tf"] is True
    engine.warmup()
    checked = _assert_parity(df, df_e, index, engine, "tf_match_probability")
    oracle = QueryEngine(index, fused=False)
    checked_or = _assert_parity(
        df, df_e, index, oracle, "tf_match_probability"
    )
    engine.save_aot()
    top_p, top_rows, top_valid, n_cand = engine.query_arrays(
        df.head(QUERY_HEAD)
    )
    np.savez(
        os.path.join(workdir, "answers.npz"),
        top_p=top_p, top_rows=top_rows, top_valid=top_valid, n_cand=n_cand,
    )

    # ---- legacy leg: a TF-less artifact round-trips and serves as ever ----
    df2, settings2 = fixture_corpus(tf=False)
    linker2 = Splink(settings2, df=df2)
    df_e2 = linker2.get_scored_comparisons()
    assert "tf_match_probability" not in df_e2.columns
    legacy_dir = os.path.join(workdir, "legacy_index")
    linker2.export_index(legacy_dir)
    legacy = load_index(legacy_dir)
    assert not legacy.tf_fold_columns() and not legacy.tf_tids
    eng2 = QueryEngine(legacy)
    assert not eng2.tf_active and eng2._aot_binding()["tf"] is False
    eng2.warmup()
    checked2 = _assert_parity(df2, df_e2, legacy, eng2, "match_probability")

    with open(os.path.join(workdir, "build.json"), "w") as fh:
        json.dump({"checked": checked}, fh)
    print(
        f"tf-smoke[A] OK: TF serve<->offline parity bit-identical over "
        f"{checked} fused + {checked_or} unfused served pairs, legacy "
        f"TF-less round-trip bit-identical over {checked2} pairs, TF "
        "sidecar committed"
    )
    return 0


def phase_serve(workdir: str) -> int:
    import numpy as np

    from splink_tpu.obs.metrics import compile_stats, install_compile_monitor
    from splink_tpu.serve import QueryEngine, load_index

    install_compile_monitor()
    df, _settings = fixture_corpus(tf=True)
    index_dir = os.path.join(workdir, "index")
    engine = QueryEngine(
        load_index(index_dir), aot_dir=os.path.join(index_dir, "aot")
    )
    assert engine.tf_active, "restored engine must fold (settings default)"
    warm = engine.warmup()
    assert warm["compiles"] == 0, (
        f"TF-menu AOT restore performed {warm['compiles']} backend "
        f"compiles (expected 0): {warm}"
    )
    assert warm["cache_hits"] == 0, warm
    assert warm["aot_restored"] == warm["combinations"] > 0, warm
    got = engine.query_arrays(df.head(QUERY_HEAD))
    stats = compile_stats()
    assert stats["compiles"] == 0 and stats["requests"] == 0, stats
    ref = np.load(os.path.join(workdir, "answers.npz"))
    for name, g in zip(("top_p", "top_rows", "top_valid", "n_cand"), got):
        e = ref[name]
        assert e.dtype == g.dtype and e.shape == g.shape, name
        assert np.array_equal(e, g), (
            f"restored TF engine's {name} differs from process A "
            "(bit-identity required)"
        )
    print(
        "tf-smoke[B] OK: "
        f"{warm['aot_restored']}/{warm['combinations']} TF executables "
        "AOT-restored with 0 backend compiles and 0 cache reads, "
        f"{QUERY_HEAD} first-query TF-adjusted scores bit-identical to "
        "process A"
    )
    return 0


def main() -> int:
    if len(sys.argv) >= 3 and sys.argv[1] == "--phase":
        phase, workdir = sys.argv[2], sys.argv[3]
        return phase_build(workdir) if phase == "build" else phase_serve(workdir)
    with tempfile.TemporaryDirectory(prefix="tf_smoke_") as workdir:
        env = dict(os.environ)
        # hermetic: phase B asserts cache_hits == 0, so neither phase may
        # touch the user's persistent compile cache
        env["JAX_COMPILATION_CACHE_DIR"] = os.path.join(workdir, "xla_cache")
        for phase in ("build", "serve"):
            rc = subprocess.call(
                [sys.executable, os.path.abspath(__file__),
                 "--phase", phase, workdir],
                env=env, cwd=REPO,
            )
            if rc != 0:
                print(f"tf-smoke FAILED in phase {phase} (rc={rc})")
                return rc
    return 0


if __name__ == "__main__":
    sys.exit(main())
