"""Approximate-blocking smoke (`make approx-smoke`): gate the four
contracts of the minhash-LSH recall tier end to end:

  1. determinism — two independent runs over the same corpus produce the
     IDENTICAL candidate emission (fixed-seed minhash, deterministic
     ranking);
  2. budget — the emitted approx pair count never exceeds
     ``approx_pair_budget`` and the exact tier's pairs always ride along;
  3. zero steady-state recompiles — re-running candidate generation over
     the same (already warmed) chunk shapes keeps the jax.monitoring
     compile counter flat;
  4. serve fallback parity — garbled queries (typo in EVERY blocking key)
     return approx-tagged candidates through the LSH fallback bucket
     path, bit-identical in score to a host-side oracle that re-derives
     the band buckets from the same fixed-seed signatures and scores the
     pairs offline.

Exits nonzero on any violation. Runs on any backend (CPU tier included).
"""

import os
import sys
import warnings

os.environ.setdefault("JAX_PLATFORMS", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _corpus(n=60, seed=5):
    import numpy as np
    import pandas as pd

    rng = np.random.default_rng(seed)
    firsts = ["amelia", "oliver", "isla", "george", "ava", "noah"]
    lasts = ["smith", "jones", "taylor", "brown", "wilson"]
    base = pd.DataFrame(
        {
            "unique_id": range(n),
            "first_name": [f"{rng.choice(firsts)}{k:02d}" for k in range(n)],
            "surname": [f"{rng.choice(lasts)}{k:02d}" for k in range(n)],
        }
    )
    twins = base.copy()
    twins["unique_id"] = twins["unique_id"] + n
    crng = np.random.default_rng(seed + 1)

    def corrupt(v):
        k = int(crng.integers(0, len(v)))
        return v[:k] + "#" + v[k + 1 :]

    twins["first_name"] = [corrupt(v) for v in twins["first_name"]]
    twins["surname"] = [corrupt(v) for v in twins["surname"]]
    return base, twins


def main() -> int:
    import numpy as np
    import pandas as pd

    from splink_tpu import Splink
    from splink_tpu.blocking import block_using_rules
    from splink_tpu.data import encode_table
    from splink_tpu.obs.metrics import (
        compile_requests,
        install_compile_monitor,
    )
    from splink_tpu.serve import BucketPolicy, QueryEngine
    from splink_tpu.settings import complete_settings_dict

    install_compile_monitor()
    base, twins = _corpus()
    df = pd.concat([base, twins], ignore_index=True)
    n = len(base)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        settings = complete_settings_dict(
            {
                "link_type": "dedupe_only",
                "comparison_columns": [
                    {"col_name": "first_name", "num_levels": 3},
                    {
                        "col_name": "surname",
                        "num_levels": 2,
                        "comparison": {"kind": "exact"},
                    },
                ],
                "blocking_rules": [
                    "l.first_name = r.first_name",
                    "l.surname = r.surname",
                ],
                "max_iterations": 3,
                "approx_blocking": True,
                "approx_threshold": 0.2,
                "approx_pair_budget": 4 * n,
            }
        )

    # 1. determinism across two full runs + 2. budget cap
    table = encode_table(df, settings)
    p1 = block_using_rules(settings, table)
    p2 = block_using_rules(settings, encode_table(df, settings))
    assert np.array_equal(p1.idx_l, p2.idx_l) and np.array_equal(
        p1.idx_r, p2.idx_r
    ), "approx candidate emission is not deterministic across runs"
    exact_cfg = dict(settings)
    exact_cfg["approx_blocking"] = False
    pe = block_using_rules(exact_cfg, encode_table(df, exact_cfg))
    n_approx = p1.n_pairs - pe.n_pairs
    assert 0 < n_approx <= settings["approx_pair_budget"], (
        f"approx emitted {n_approx} pairs against budget "
        f"{settings['approx_pair_budget']}"
    )
    true = {(k, k + n) for k in range(n)}
    got = set(zip(p1.idx_l.tolist(), p1.idx_r.tolist()))
    recall = len(true & got) / len(true)
    assert recall >= 0.95, f"approx recall {recall:.2f} below the 95% bar"

    # 3. zero steady-state recompiles across chunk shapes: re-drive
    # candidate generation over the SAME plan (the blocking-smoke
    # contract — per-band emit kernels are cached on the plan, the
    # minhash/verify kernels in module-level lru caches)
    from splink_tpu.approx.lsh import (
        build_approx_plan,
        generate_approx_candidates,
    )

    plan = build_approx_plan(settings, table)
    assert plan is not None
    generate_approx_candidates(settings, table, plan=plan)  # warm
    c0 = compile_requests()
    res = generate_approx_candidates(settings, table, plan=plan)
    assert res is not None
    assert compile_requests() - c0 == 0, "steady-state approx recompiled"

    # 4. serve fallback parity with a host-side oracle
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        linker = Splink(dict(settings), df=base)
        linker.get_scored_comparisons()
        index = linker.export_index()
        assert index.approx is not None
        eng = QueryEngine(
            index, top_k=8, policy=BucketPolicy((16, 64), (64, 256))
        )
        eng.warmup()
        approx_out = []
        top_p, top_rows, top_valid, _ = eng.query_arrays(
            twins, approx_out=approx_out
        )
        assert approx_out[0].any(), "no query took the fallback bucket path"
        # oracle: offline scoring (no EM) over base+twins with the SAME
        # params; its approx tier re-derives the same fixed-seed band
        # buckets, so every fallback pair must appear with a bit-identical
        # score
        import copy

        s2 = copy.deepcopy(linker.settings)
        s2["max_iterations"] = 0
        s2["approx_pair_budget"] = 1 << 20
        oracle = Splink(s2, df=df)
        oracle.params = linker.params
        df_e = oracle.get_scored_comparisons()
    offline = {
        (int(r["unique_id_l"]), int(r["unique_id_r"])): r["match_probability"]
        for _, r in df_e.iterrows()
    }
    checked = 0
    for q in range(len(twins)):
        for r in range(top_p.shape[1]):
            if not top_valid[q, r]:
                continue
            m = int(index.unique_id[top_rows[q, r]])
            key = (m, q + n)
            if key in offline:
                assert np.float32(offline[key]) == top_p[q, r], (
                    f"serve fallback score drifted from the offline oracle "
                    f"for pair {key}"
                )
                checked += 1
    assert checked >= n, f"parity covered only {checked} pairs"

    print(
        "approx-smoke OK: "
        f"{n_approx} approx pairs (budget {settings['approx_pair_budget']}, "
        f"recall {recall:.0%}) deterministic across runs, 0 steady-state "
        f"recompiles, serve fallback parity over {checked} scored pairs"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
