"""TPU smoke tier configuration.

Unlike tests/conftest.py (which forces an 8-virtual-device CPU platform for
the oracle/golden tier), this tier runs on whatever accelerator backend the
environment provides and skips everything when none is present. It exists so
TPU *lowering* is exercised by the suite — the round-1 Pallas iota bug shipped
precisely because every Pallas test passed interpret=True.

Run with: make tpu-smoke   (or: python -m pytest tests_tpu/ -q)
It must be a separate pytest invocation from tests/ — the unit tier's
conftest pins the process to CPU before jax initialises.
"""

import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from _device_probe import probe_device_init  # noqa: E402

if os.environ.get("SPLINK_TPU_SKIP_BACKEND_PROBE") == "1":
    _BACKEND_OK, _PROBE_DETAIL = True, ""
else:
    # Probe in a killable subprocess BEFORE any jax import: a dead
    # accelerator tunnel blocks `import jax` inside C code where pytest can
    # neither time out nor interrupt. When the probe fails, test modules
    # must not even be COLLECTED — their own top-level jax imports would
    # hang the session (pytest_ignore_collect below).
    _BACKEND_OK, _PROBE_DETAIL = probe_device_init()
    if not _BACKEND_OK:
        sys.stderr.write(
            f"tests_tpu: skipping collection — {_PROBE_DETAIL}\n"
            "(note: pytest exits 5 when nothing is collected; "
            "`make tpu-smoke` treats that as a skip)\n"
        )

if _BACKEND_OK:
    import jax

    from splink_tpu.ops.strings_pallas import TPU_BACKENDS


def pytest_ignore_collect(collection_path, config):
    # an unreachable backend means no test module is safe to import
    if not _BACKEND_OK:
        return True
    return None


def pytest_collection_modifyitems(config, items):
    if not _BACKEND_OK:
        return
    if jax.default_backend() not in TPU_BACKENDS:
        skip = pytest.mark.skip(reason="no TPU backend present")
        for item in items:
            item.add_marker(skip)


@pytest.fixture
def rng():
    return np.random.default_rng(1234)


@pytest.fixture
def string_batch(rng):
    """~1k variable-length lowercase ASCII pairs, incl. duplicates/transposes."""
    B, L = 1024, 24
    lens1 = rng.integers(0, L + 1, B).astype(np.int32)
    lens2 = rng.integers(0, L + 1, B).astype(np.int32)
    s1 = (rng.integers(97, 123, (B, L)) * (np.arange(L) < lens1[:, None])).astype(
        np.uint8
    )
    s2 = (rng.integers(97, 123, (B, L)) * (np.arange(L) < lens2[:, None])).astype(
        np.uint8
    )
    # make a slice of exact duplicates and near-duplicates (transpositions)
    s2[:256], lens2[:256] = s1[:256], lens1[:256]
    for i in range(128, 256):
        if lens1[i] >= 2:
            j = int(rng.integers(0, lens1[i] - 1))
            s2[i, j], s2[i, j + 1] = s2[i, j + 1], s2[i, j]
    return s1, s2, lens1, lens2
