"""TPU smoke tier configuration.

Unlike tests/conftest.py (which forces an 8-virtual-device CPU platform for
the oracle/golden tier), this tier runs on whatever accelerator backend the
environment provides and skips everything when none is present. It exists so
TPU *lowering* is exercised by the suite — the round-1 Pallas iota bug shipped
precisely because every Pallas test passed interpret=True.

Run with: make tpu-smoke   (or: python -m pytest tests_tpu/ -q)
It must be a separate pytest invocation from tests/ — the unit tier's
conftest pins the process to CPU before jax initialises.
"""

import jax
import numpy as np
import pytest

from splink_tpu.ops.strings_pallas import TPU_BACKENDS


def pytest_collection_modifyitems(config, items):
    if jax.default_backend() not in TPU_BACKENDS:
        skip = pytest.mark.skip(reason="no TPU backend present")
        for item in items:
            item.add_marker(skip)


@pytest.fixture
def rng():
    return np.random.default_rng(1234)


@pytest.fixture
def string_batch(rng):
    """~1k variable-length lowercase ASCII pairs, incl. duplicates/transposes."""
    B, L = 1024, 24
    lens1 = rng.integers(0, L + 1, B).astype(np.int32)
    lens2 = rng.integers(0, L + 1, B).astype(np.int32)
    s1 = (rng.integers(97, 123, (B, L)) * (np.arange(L) < lens1[:, None])).astype(
        np.uint8
    )
    s2 = (rng.integers(97, 123, (B, L)) * (np.arange(L) < lens2[:, None])).astype(
        np.uint8
    )
    # make a slice of exact duplicates and near-duplicates (transpositions)
    s2[:256], lens2[:256] = s1[:256], lens1[:256]
    for i in range(128, 256):
        if lens1[i] >= 2:
            j = int(rng.integers(0, lens1[i] - 1))
            s2[i, j], s2[i, j + 1] = s2[i, j + 1], s2[i, j]
    return s1, s2, lens1, lens2
