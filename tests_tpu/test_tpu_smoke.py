"""Hardware smoke tier: compile and run the TPU-only code paths for real.

Covers the gap that shipped the round-1 regression: Pallas kernels were only
ever tested with interpret=True, so Mosaic lowering was never exercised. Each
test here runs the real compiled artifact on the chip and checks values
against the vmapped JAX implementations (which are themselves oracle-tested
in the CPU tier, tests/test_string_kernels.py).

Reference analogue: the "real engine" Spark tier of the reference suite
(/root/reference/tests/test_spark.py:22-68).
"""

import jax.numpy as jnp
import numpy as np
import pandas as pd


def _dev(*arrays):
    return tuple(jnp.asarray(a) for a in arrays)


class TestPallasKernelsOnHardware:
    def test_jaro_winkler_matches_vmapped(self, string_batch):
        from splink_tpu.ops import strings
        from splink_tpu.ops.strings_pallas import jaro_winkler_pallas

        s1, s2, l1, l2 = _dev(*string_batch)
        got = np.asarray(jaro_winkler_pallas(s1, s2, l1, l2))
        want = np.asarray(strings.jaro_winkler_vmapped(s1, s2, l1, l2, 0.1, 0.7))
        np.testing.assert_allclose(got, want, atol=1e-6)

    def test_jaro_winkler_known_value(self):
        from splink_tpu.ops.strings_pallas import jaro_winkler_pallas

        m1 = np.zeros((1, 16), np.uint8)
        m2 = np.zeros((1, 16), np.uint8)
        m1[0, :6] = np.frombuffer(b"MARTHA", np.uint8)
        m2[0, :6] = np.frombuffer(b"MARHTA", np.uint8)
        v = float(jaro_winkler_pallas(*_dev(m1, m2, [6], [6]))[0])
        assert abs(v - 0.9611) < 1e-3

    def test_levenshtein_matches_vmapped(self, string_batch):
        from splink_tpu.ops import strings
        from splink_tpu.ops.strings_pallas import levenshtein_pallas

        s1, s2, l1, l2 = _dev(*string_batch)
        got = np.asarray(levenshtein_pallas(s1, s2, l1, l2))
        want = np.asarray(strings.levenshtein_vmapped(s1, s2, l1, l2))
        np.testing.assert_allclose(got, want.astype(np.float32), atol=0)

    def test_dispatch_selects_pallas_on_tpu(self):
        from splink_tpu.ops.strings_pallas import pallas_supported

        a = jnp.zeros((8, 24), jnp.uint8)
        assert pallas_supported(a)


class TestPipelineOnHardware:
    def test_linker_end_to_end_on_device(self):
        """Full Splink flow — blocking, gamma program, fused EM — on the chip.

        Uses a jaro_winkler string column so the GammaProgram routes through
        the Pallas kernel (non-interpret)."""
        import splink_tpu

        rng = np.random.default_rng(7)
        names = ["olivia", "liam", "emma", "noah", "amelia", "oliver",
                 "sophia", "elijah", "isabella", "lucas"]
        rows = []
        for i in range(150):
            f = names[rng.integers(len(names))] + str(rng.integers(100))
            city = ["london", "leeds", "york", "bath"][rng.integers(4)]
            rows.append({"unique_id": 2 * i, "name": f, "city": city})
            g = list(f)
            g[1], g[2] = g[2], g[1]
            rows.append({"unique_id": 2 * i + 1, "name": "".join(g), "city": city})
        df = pd.DataFrame(rows)
        settings = {
            "link_type": "dedupe_only",
            "blocking_rules": ["l.city = r.city"],
            "comparison_columns": [
                {"col_name": "name", "data_type": "string", "num_levels": 3},
                {"col_name": "city", "data_type": "string", "num_levels": 2},
            ],
            "max_iterations": 10,
        }
        linker = splink_tpu.Splink(settings, df=df)
        scored = linker.get_scored_comparisons()
        dup = scored[(scored.unique_id_l // 2) == (scored.unique_id_r // 2)]
        non = scored[(scored.unique_id_l // 2) != (scored.unique_id_r // 2)]
        assert dup.match_probability.median() > 0.8
        assert non.match_probability.median() < 0.5

    def test_run_em_on_device(self):
        from splink_tpu.em import run_em
        from splink_tpu.models.fellegi_sunter import FSParams

        rng = np.random.default_rng(3)
        C, N = 4, 50_000
        m_t = np.tile([0.05, 0.1, 0.85], (C, 1))
        u_t = np.tile([0.7, 0.2, 0.1], (C, 1))
        is_m = rng.random(N) < 0.25
        G = np.zeros((N, C), np.int8)
        for c in range(C):
            G[:, c] = np.where(
                is_m, rng.choice(3, N, p=m_t[c]), rng.choice(3, N, p=u_t[c])
            )
        params0 = FSParams(
            lam=jnp.asarray(0.5),
            m=jnp.asarray(np.tile([0.1, 0.2, 0.7], (C, 1))),
            u=jnp.asarray(np.tile([0.7, 0.2, 0.1], (C, 1))),
        )
        out = run_em(
            jnp.asarray(G), params0, max_levels=3, max_iterations=40,
            em_convergence=1e-6,
        )
        assert abs(float(out.params.lam) - 0.25) < 0.02
        assert np.abs(np.asarray(out.params.m) - m_t).max() < 0.03


class TestCaseCompilerOnHardware:
    def test_case_sql_gamma_on_device(self):
        """A hand-written case_expression (general CASE compiler) lowers and
        runs inside the jitted gamma program on the chip."""
        from splink_tpu.data import encode_table
        from splink_tpu.gammas import GammaProgram
        from splink_tpu.settings import complete_settings_dict

        df = pd.DataFrame(
            {
                "unique_id": range(6),
                "name": ["martha", "martha", "marhta", "marx", "zz", None],
                "age": [40.0, 41.0, 39.0, 80.0, 40.0, None],
            }
        )
        expr = """case
            when name_l is null or name_r is null then -1
            when name_l = name_r and abs(age_l - age_r) <= 1 then 2
            when jaro_winkler_sim(name_l, name_r) > 0.9 then 1
            else 0 end"""
        s = complete_settings_dict(
            {
                "link_type": "dedupe_only",
                "comparison_columns": [
                    {
                        "custom_name": "combo",
                        "custom_columns_used": ["name", "age"],
                        "num_levels": 3,
                        "case_expression": expr,
                    }
                ],
                "blocking_rules": ["l.unique_id = r.unique_id"],
            }
        )
        table = encode_table(df, s)
        prog = GammaProgram(s, table)
        G = prog.compute(
            np.zeros(5, np.int64), np.arange(1, 6, dtype=np.int64)
        )
        assert G[:, 0].tolist() == [2, 1, 0, 0, -1]


class TestFloat64FallbackOnHardware:
    def test_float64_setting_warns_and_runs_f32_on_tpu(self):
        """TPU has no float64: the setting must warn and fall back to
        float32 rather than enabling x64 and failing to lower."""
        import warnings

        import splink_tpu

        df = pd.DataFrame(
            {
                "unique_id": range(40),
                "name": [f"n{i % 7}" for i in range(40)],
                "city": ["a", "b"] * 20,
            }
        )
        settings = {
            "link_type": "dedupe_only",
            "blocking_rules": ["l.city = r.city"],
            "comparison_columns": [
                {"col_name": "name", "comparison": {"kind": "exact"}}
            ],
            "float64": True,
            "max_iterations": 3,
        }
        linker = splink_tpu.Splink(settings, df=df)
        with warnings.catch_warnings(record=True) as caught:
            warnings.simplefilter("always")
            out = linker.get_scored_comparisons()
        assert out.match_probability.dtype == np.float32
        assert any("float64" in str(w.message) for w in caught)


class TestVirtualPairsOnHardware:
    def test_device_pair_generation_matches_materialised(self):
        """The virtual pair index (pairs decoded ON the chip from unit
        structure) scores identically to the materialised pattern pipeline
        on real hardware — int32 searchsorted, f32 triangle decode, masks
        and histogram all lower to the device."""
        import splink_tpu

        rng = np.random.default_rng(11)
        n = 4000
        df = pd.DataFrame(
            {
                "unique_id": np.arange(n),
                "name": rng.choice(
                    ["ann", "bob", "cat", "dan", None], n
                ),
                "dob": rng.choice([f"d{k}" for k in range(40)], n),
                "postcode": rng.choice([f"p{k}" for k in range(25)], n),
            }
        )
        base = {
            "link_type": "dedupe_only",
            "comparison_columns": [
                {"col_name": "name", "num_levels": 3},
            ],
            # second rule carries a residual predicate: it lowers to an
            # on-device mask inside the virtual kernel
            "blocking_rules": [
                "l.dob = r.dob",
                "l.postcode = r.postcode and l.name != r.name",
            ],
            "max_resident_pairs": 2048,  # force the streamed regime
            "max_iterations": 4,
        }
        on = splink_tpu.Splink(
            dict(base, device_pair_generation="on"), df=df
        )
        a = on.get_scored_comparisons()
        assert on._virtual is not None
        off = splink_tpu.Splink(
            dict(base, device_pair_generation="off"), df=df
        )
        b = off.get_scored_comparisons()
        key = ["unique_id_l", "unique_id_r"]
        a = a.sort_values(key).reset_index(drop=True)
        b = b.sort_values(key).reset_index(drop=True)
        assert len(a) == len(b)
        np.testing.assert_array_equal(a[key].to_numpy(), b[key].to_numpy())
        np.testing.assert_allclose(
            a.match_probability, b.match_probability, rtol=1e-6
        )

    def test_overlap_blocking_on_device(self):
        """Blocking/scoring overlap on the chip: async device dispatch
        during host joins, bitwise-equal scores vs sequential."""
        import splink_tpu

        rng = np.random.default_rng(13)
        n = 3000
        df = pd.DataFrame(
            {
                "unique_id": np.arange(n),
                "name": rng.choice(["ann", "bob", "cat", "dan"], n),
                "dob": rng.choice([f"d{k}" for k in range(30)], n),
            }
        )
        base = {
            "link_type": "dedupe_only",
            "comparison_columns": [{"col_name": "name", "num_levels": 2}],
            "blocking_rules": ["l.dob = r.dob"],
            "max_iterations": 3,
            "device_pair_generation": "off",
        }
        a = splink_tpu.Splink(dict(base), df=df).get_scored_comparisons()
        b = splink_tpu.Splink(
            dict(base, overlap_blocking=False), df=df
        ).get_scored_comparisons()
        key = ["unique_id_l", "unique_id_r"]
        a = a.sort_values(key).reset_index(drop=True)
        b = b.sort_values(key).reset_index(drop=True)
        np.testing.assert_allclose(
            a.match_probability, b.match_probability, rtol=0, atol=0
        )


class TestRound4OnHardware:
    """Round-4 surfaces on the real chip: derived blocking keys feeding
    the virtual pair index, device function-residual masks, and the
    jar-exact charset-Jaccard kernel."""

    def test_derived_keys_and_function_residuals_on_device(self):
        from splink_tpu import Splink

        rng = np.random.default_rng(61)
        n = 3000
        df = pd.DataFrame(
            {
                "unique_id": np.arange(n),
                "surname": rng.choice(
                    ["smithson", "smithers", "smyth", "jones", "jonas", None],
                    n,
                ),
                "first_name": rng.choice(["ann", "bob", "cat"], n),
                "city": rng.choice([f"c{k}" for k in range(10)], n),
            }
        )
        base = {
            "link_type": "dedupe_only",
            "comparison_columns": [
                {"col_name": "first_name", "num_levels": 3}
            ],
            "blocking_rules": [
                "substr(l.surname, 1, 3) = substr(r.surname, 1, 3)",
                "l.city = r.city and length(l.surname) = length(r.surname)",
            ],
            "max_iterations": 4,
            "max_resident_pairs": 1024,
        }
        key = ["unique_id_l", "unique_id_r"]
        on = (
            Splink(dict(base, device_pair_generation="on"), df=df)
            .get_scored_comparisons()
            .sort_values(key)
            .reset_index(drop=True)
        )
        off = (
            Splink(dict(base, device_pair_generation="off"), df=df)
            .get_scored_comparisons()
            .sort_values(key)
            .reset_index(drop=True)
        )
        assert len(on) == len(off) and len(on) > 1000
        np.testing.assert_array_equal(
            on[key].to_numpy(), off[key].to_numpy()
        )
        np.testing.assert_allclose(
            on.match_probability, off.match_probability, rtol=1e-5
        )

    def test_charset_jaccard_on_device_matches_golden(self):
        """The jar-exact charset Jaccard must survive real XLA:TPU
        lowering (integer-form rounding in f32)."""
        import json
        import os

        from splink_tpu.data import encode_string_column
        from splink_tpu.ops.qgram import charset_jaccard

        path = os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "tests", "data", "jar_similarity_vectors.json",
        )
        with open(path) as fh:
            vectors = json.load(fh)[:256]
        a = encode_string_column([v["a"] for v in vectors], width=32)
        b = encode_string_column([v["b"] for v in vectors], width=32)
        w = max(a.bytes_.shape[1], b.bytes_.shape[1])
        pa = np.pad(a.bytes_, ((0, 0), (0, w - a.bytes_.shape[1])))
        pb = np.pad(b.bytes_, ((0, 0), (0, w - b.bytes_.shape[1])))
        got = np.asarray(
            charset_jaccard(*_dev(pa, pb, a.lengths, b.lengths), None),
            np.float64,
        )
        jar = np.array([v["jaccard"] for v in vectors])
        # exact ties may differ by 0.01 (jar f64 artifact) — allow those
        assert (np.abs(got - jar) < 0.0101).all()
        assert (np.abs(got - jar) < 1e-6).mean() > 0.95


class TestMaskedQgramOnHardware:
    def test_masked_qgram_matches_self_contained_on_device(self):
        """The precomputed-aux q-gram kernels (packed mask/count/norm
        lanes, cross-matrix-only per pair) must lower and bit-match the
        self-contained kernels on real XLA:TPU."""
        from splink_tpu.data import encode_string_column
        from splink_tpu.ops import qgram

        rng = np.random.default_rng(13)
        vals = ["".join(rng.choice(list("abcdef"), rng.integers(1, 18)))
                for _ in range(300)] + ["", None]
        col = encode_string_column(
            np.array(rng.choice(np.array(vals, object), 4096), object),
            width=24,
        )
        q = 2
        mask, count, sumsq = qgram.qgram_row_aux(
            col.bytes_, col.lengths, col.token_ids, q
        )
        il = rng.integers(0, len(col.lengths), 4096)
        ir = rng.integers(0, len(col.lengths), 4096)
        s1, s2, l1, l2 = _dev(
            col.bytes_[il], col.bytes_[ir], col.lengths[il], col.lengths[ir]
        )
        plain = np.asarray(qgram.qgram_jaccard(s1, s2, l1, l2, q))
        fast = np.asarray(
            qgram.qgram_jaccard_masked(
                s1, s2, l1, l2,
                *_dev(mask[il], count[il], count[ir]), q,
            )
        )
        np.testing.assert_array_equal(plain, fast)
        plain_c = np.asarray(qgram.qgram_cosine_distance(s1, s2, l1, l2, q))
        fast_c = np.asarray(
            qgram.qgram_cosine_masked(
                s1, s2, l1, l2, *_dev(sumsq[il], sumsq[ir]), q
            )
        )
        np.testing.assert_array_equal(plain_c, fast_c)

    def test_six_column_virtual_histogram_with_masked_qgram(self):
        """Config-4-shaped program (JW x3, exact x2, masked qgram) through
        the virtual pair index on device: histogram must match the
        materialised pattern pass bit-for-bit."""
        from splink_tpu import Splink
        from splink_tpu.gammas import _qgram_key

        rng = np.random.default_rng(17)
        n = 4000
        firsts = [f"fn{i:03d}" for i in range(60)]
        surs = [f"sur{i:03d}" for i in range(80)]
        df = pd.DataFrame(
            {
                "unique_id": np.arange(n),
                "first_name": rng.choice(firsts, n),
                "surname": rng.choice(surs, n),
                "dob": rng.choice([f"19{k:02d}-01-01" for k in range(40)], n),
                "city": rng.choice([f"c{k}" for k in range(12)], n),
                "postcode": rng.choice([f"p{k:04d}" for k in range(300)], n),
            }
        )
        cols = [
            {"col_name": "first_name", "num_levels": 3},
            {"col_name": "surname", "num_levels": 3},
            {"col_name": "dob", "comparison": {"kind": "exact"}},
            {"col_name": "city", "comparison": {"kind": "exact"}},
            {"col_name": "postcode", "num_levels": 2},
            {"custom_name": "surname_qgram", "custom_columns_used": ["surname"],
             "num_levels": 2,
             "comparison": {"kind": "qgram_jaccard", "column": "surname",
                            "thresholds": [0.6]}},
        ]
        base = {
            "link_type": "dedupe_only",
            "comparison_columns": cols,
            "blocking_rules": ["l.dob = r.dob", "l.postcode = r.postcode"],
            "max_iterations": 3,
        }
        lk_virtual = Splink(
            {**base, "device_pair_generation": "on", "max_resident_pairs": 1024},
            df=df,
        )
        assert lk_virtual._virtual_plan() is not None
        _, counts_v, prog = lk_virtual._ensure_pattern_ids()
        assert _qgram_key("surname", 2) in prog._layout
        lk_host = Splink(
            {**base, "device_pair_generation": "off"}, df=df
        )
        _, counts_h, _ = lk_host._ensure_pattern_ids()
        np.testing.assert_array_equal(np.asarray(counts_v), np.asarray(counts_h))
