"""Shared accelerator-init probe (bench.py and tests_tpu/conftest.py).

A dead accelerator tunnel can make ``import jax`` / device init block
FOREVER inside a C-level call where no Python signal fires. Probing in a
subprocess is the only reliable guard: a subprocess can always be killed
(as a group — helpers a plugin forks must die too).

Lives at the repo root, NOT inside splink_tpu: importing anything under the
package would itself import jax and hang under the exact condition being
probed.
"""

from __future__ import annotations

import os
import subprocess
import sys
import tempfile

DEFAULT_TIMEOUT_S = 600


def probe_device_init(timeout_s: int | None = None) -> tuple[bool, str]:
    """-> (ok, detail). ok=True when ``import jax; jax.devices()`` completes
    in a fresh subprocess. detail distinguishes a timeout (tunnel hang) from
    a fast failure (broken install — stderr tail included)."""
    if timeout_s is None:
        timeout_s = int(
            os.environ.get(
                "SPLINK_TPU_INIT_TIMEOUT",
                os.environ.get(
                    "SPLINK_TPU_BENCH_INIT_TIMEOUT", DEFAULT_TIMEOUT_S
                ),
            )
        )
    # stderr goes to a FILE, not a pipe: helper processes that survive a
    # timeout kill would hold a pipe's write end open forever; a file has no
    # reader to block.
    with tempfile.TemporaryFile() as errf:
        proc = subprocess.Popen(
            [sys.executable, "-c", "import jax; jax.devices()"],
            stdout=subprocess.DEVNULL,
            stderr=errf,
            start_new_session=True,
        )
        try:
            rc = proc.wait(timeout=timeout_s)
            errf.seek(0)
            tail = errf.read().decode(errors="replace")[-300:].strip()
            if rc == 0:
                return True, ""
            return False, f"device init failed (rc={rc}): {tail}"
        except subprocess.TimeoutExpired:
            import signal

            try:
                os.killpg(proc.pid, signal.SIGKILL)  # child + any helpers
            except (ProcessLookupError, PermissionError):
                proc.kill()
            proc.wait()
            return False, (
                f"device init did not respond within {timeout_s}s "
                "(accelerator tunnel down?)"
            )
